"""Pipeline x data parallelism through the public API (round 5).

A user's Program, cut into pipeline stages by PipelineOptimizer at
the per-layer activations BERT's builder exposes, compiled over a
(dp, pp) mesh by `with_pipeline(dp=...)`: the GPipe schedule is
manual over pp, batch sharding stays GSPMD-auto inside each stage —
one compiled executable carries both axes. The masked-mean LM loss
(reduce_sum(ce*mask)/reduce_sum(mask)) pipelines EXACTLY: numerator
and denominator aggregate separately across microbatches
(core/pipeline_program.py).

Run (8 virtual CPU devices stand in for 8 chips):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/train_pipeline_dp.py

Reference analogue: PipelineTrainer's SectionWorker threads inside
NCCL-ring trainers (framework/trainer.h:118) — here one SPMD program.
"""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.models import BertConfig, build_bert_pretrain
from paddle_tpu.models.bert import synthetic_batch


def main(steps=3, dp=2, schedule="gpipe"):
    cfg = BertConfig.tiny()
    cfg.num_layers = 4                      # 4 pipeline stages
    cfg.hidden_dropout = cfg.attention_dropout = 0.0
    main_prog, startup, _, fetches = build_bert_pretrain(
        cfg, seq_len=64, optimizer=None)
    with fluid.program_guard(main_prog, startup):
        fluid.optimizer.PipelineOptimizer(
            fluid.optimizer.Adam(1e-3),
            cut_list=fetches["encoder_outputs"][:-1],  # cut at layers
            num_microbatches=4,
            schedule=schedule,
        ).minimize(fetches["loss"])

    target = fluid.CompiledProgram(main_prog).with_pipeline(dp=dp)

    rng = np.random.RandomState(0)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        for step in range(steps):
            batch = synthetic_batch(rng, 8, 64, cfg.vocab_size)
            (loss,) = exe.run(target, feed=batch,
                              fetch_list=[fetches["loss"]])
            print(f"step {step} pp4 x dp{dp} [{schedule}] "
                  f"loss {float(np.asarray(loss)):.4f}")
    print("pipeline x dp training OK")


if __name__ == "__main__":
    main()

"""Authoring half of the native-trainer demo (reference
train/demo/demo_network.py): build a regression program pair in python
and serialize it; examples/native_trainer.c then trains it with no
Python driver in the loop.

  python examples/author_trainer_program.py /tmp/model
  gcc examples/native_trainer.c -o ctrainer \
      -Lpaddle_tpu/capi/build -lpaddle_capi \
      -Wl,-rpath,paddle_tpu/capi/build $(python3-config --ldflags --embed)
  ./ctrainer /tmp/model/main.json /tmp/model/startup.json <loss> /tmp/ck
(the authoring script prints the loss var name)."""

import os
import sys

import paddle_tpu as fluid
from paddle_tpu import layers


def main():
    out_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/paddle_tpu_demo"
    os.makedirs(out_dir, exist_ok=True)
    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 13
    with fluid.program_guard(main_prog, startup), fluid.unique_name.guard():
        x = layers.data("x", [4])
        y = layers.data("y", [1])
        pred = layers.fc(x, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.5).minimize(loss)
    with open(os.path.join(out_dir, "main.json"), "w") as f:
        f.write(main_prog.to_json())
    with open(os.path.join(out_dir, "startup.json"), "w") as f:
        f.write(startup.to_json())
    print(out_dir)
    print(loss.name)


if __name__ == "__main__":
    main()

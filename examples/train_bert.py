"""BERT pretraining with flash attention + bf16 AMP (BASELINE config 3;
reference ERNIE/BERT fleet scripts)."""

import argparse

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.contrib.mixed_precision import decorate
from paddle_tpu.models import BertConfig, build_bert_pretrain
from paddle_tpu.models.bert import synthetic_batch


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--config", default="tiny",
                    choices=["tiny", "base", "large"])
    ap.add_argument("--flash", action="store_true",
                    help="fused Pallas flash attention (TPU)")
    args = ap.parse_args()

    cfg = getattr(BertConfig, args.config)()
    cfg.use_flash_attention = args.flash
    opt = decorate(fluid.optimizer.Adam(1e-4), init_loss_scaling=1.0,
                   use_dynamic_loss_scaling=False, dest_dtype="bfloat16")
    main_prog, startup, feeds, fetches = build_bert_pretrain(
        cfg, args.seq, optimizer=opt)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)

    rng = np.random.RandomState(0)
    for step in range(args.steps):
        batch = synthetic_batch(rng, args.batch, args.seq, cfg.vocab_size)
        (loss,) = exe.run(main_prog, feed=batch,
                          fetch_list=[fetches["loss"]])
        print(f"step {step}: loss={float(np.asarray(loss)):.4f}")


if __name__ == "__main__":
    main()

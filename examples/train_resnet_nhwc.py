"""ResNet-50 flipped to the TPU-native channels-last layout by the
auto_nhwc program pass — model code stays NCHW (reference layout);
the pass rewrites the program (transpiler/layout.py)."""

import argparse

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.models import build_resnet50
from paddle_tpu.transpiler import auto_nhwc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--image-size", type=int, default=64)
    args = ap.parse_args()

    main_prog, startup, feeds, fetches = build_resnet50(
        num_classes=10, image_size=args.image_size)
    with fluid.program_guard(main_prog, startup), \
            fluid.unique_name.guard():
        n = auto_nhwc(main_prog)
        fluid.optimizer.Momentum(1e-2, 0.9).minimize(fetches["loss"])
    print(f"auto_nhwc flipped {n} ops to channels-last")

    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    for step in range(args.steps):
        feed = {"image": rng.randn(args.batch, 3, args.image_size,
                                   args.image_size).astype("f"),
                "label": rng.randint(0, 10, (args.batch, 1)).astype("int64")}
        (loss,) = exe.run(main_prog, feed=feed,
                          fetch_list=[fetches["loss"]])
        print(f"step {step}: loss={float(np.asarray(loss)):.4f}")


if __name__ == "__main__":
    main()

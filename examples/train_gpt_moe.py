"""GPT with switch-MoE FFNs under expert parallelism: expert weights
and Adam moments shard over the `ep` mesh axis, tokens route via
all-to-all (beyond the reference — SURVEY §2f EP axis)."""

import argparse

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.models.gpt import GPTConfig, build_gpt_lm, \
    synthetic_lm_batch


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--ep", type=int, default=4)
    ap.add_argument("--experts", type=int, default=8)
    args = ap.parse_args()

    cfg = GPTConfig.tiny()
    cfg.moe_every, cfg.moe_experts = 1, args.experts
    main_prog, startup, feeds, fetches = build_gpt_lm(
        cfg, args.seq, optimizer=fluid.optimizer.Adam(1e-3))
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)

    prog = fluid.CompiledProgram(main_prog).with_expert_parallel(
        ep=args.ep, dispatch="alltoall",
        places=[fluid.TPUPlace(i) for i in range(args.ep)])
    rng = np.random.RandomState(0)
    for step in range(args.steps):
        batch = synthetic_lm_batch(rng, args.batch, args.seq,
                                   cfg.vocab_size)
        (loss,) = exe.run(prog, feed=batch, fetch_list=[fetches["loss"]])
        print(f"step {step}: loss={float(np.asarray(loss)):.4f}")


if __name__ == "__main__":
    main()

"""Dynamic-batching serving with paddle_tpu.serving (PR 3).

`serve_bucketed.py` showed the shape-bucket trick with ONE caller
hand-rolling a loop around `Predictor.run`. Real serving is many
concurrent callers — and on TPU, N concurrent batch-1 calls waste the
systolic array N times over. `ServingEngine` coalesces them: requests
queue, the micro-batcher packs compatible ones (same shape bucket)
into a dense batch up to `max_batch_size` rows or `batch_timeout_ms`,
a pool of `Predictor.clone()` workers runs it (clones share compiled
executables via the dispatch cache), and each caller gets exactly its
own rows back. Admission control (`Overloaded`), per-request
deadlines, serving metrics, and a stdlib HTTP front end ride along.

Run:
  JAX_PLATFORMS=cpu python examples/serve_engine.py
"""

import http.client
import json
import os
import sys
import threading

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from serve_bucketed import export_model  # noqa: E402 — same demo model

from paddle_tpu.inference import Config, create_predictor  # noqa: E402
from paddle_tpu.serving import ServingEngine, ServingServer  # noqa: E402


def main(tmpdir="/tmp/pt_engine_model"):
    export_model(tmpdir)
    cfg = Config(tmpdir)
    cfg.enable_shape_bucketing(seq_buckets=(16, 32, 64, 128),
                               pad_batch=False)
    pred = create_predictor(cfg)

    engine = ServingEngine(pred, max_batch_size=8, batch_timeout_ms=25,
                           num_workers=2)

    # 4 concurrent clients, 6 variable-length requests each — the
    # engine coalesces whatever lands inside one batch window
    rng = np.random.RandomState(0)
    requests = [[(rng.randint(1, 1000, (2, L)).astype("int64"),
                  np.ones((2, L), np.float32))
                 for L in rng.randint(5, 100, size=6)] for _ in range(4)]
    errors = []

    def client(cid):
        try:
            for ids, mask in requests[cid]:
                (probs,) = engine.predict({"ids": ids, "mask": mask},
                                          deadline_ms=30_000, timeout=120)
                assert probs.shape == (2, 5), probs.shape
        except Exception as e:  # noqa: BLE001
            errors.append(repr(e))

    threads = [threading.Thread(target=client, args=(c,)) for c in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not errors, errors

    snap = engine.metrics.snapshot()
    print(f"{snap['responses_total']} requests in {snap['batches_total']} "
          f"predictor calls (occupancy mean "
          f"{snap['batch_occupancy']['mean']}, max "
          f"{snap['batch_occupancy']['max']}), p95 latency "
          f"{snap['latency_ms']['p95']}ms")
    assert snap["responses_total"] == 24
    assert snap["batches_total"] < 24, "nothing coalesced"

    # the same engine over HTTP: /v1/predict, /healthz, /metrics
    with ServingServer(engine) as srv:
        conn = http.client.HTTPConnection(srv.host, srv.port, timeout=30)
        ids, mask = requests[0][0]
        conn.request("POST", "/v1/predict", body=json.dumps(
            {"inputs": {"ids": ids.tolist(), "mask": mask.tolist()}}),
            headers={"Content-Type": "application/json"})
        r = conn.getresponse()
        assert r.status == 200, r.status
        probs = np.array(json.loads(r.read())["outputs"][
            pred.get_output_names()[0]])
        print(f"HTTP predict -> {probs.shape}, top class "
              f"{int(probs[0].argmax())}")
        conn.request("GET", "/healthz")
        r = conn.getresponse()
        assert r.status == 200 and json.loads(r.read())["status"] == "ok"
        conn.request("GET", "/metrics")
        r = conn.getresponse()
        text = r.read().decode()
        assert "paddle_serving_batch_occupancy_mean" in text
        print("HTTP /healthz + /metrics OK")
        conn.close()

    engine.close(drain=True)
    st = engine.predictor_stats()
    print(f"predictor: {st['runs']} bucketed calls, padding waste "
          f"{st['padding_waste']:.0%}, bucket hits {st['bucket_hits']}")
    print("engine serving OK")


if __name__ == "__main__":
    main()

"""Streamed autoregressive generation with paddle_tpu.generation (PR 6).

`serve_engine.py` showed stateless predict coalescing; this is the
stateful lane: a tiny causal LM is exported, loaded into a Predictor,
and wrapped in a `GenerationEngine` — paged KV cache, continuous
batching, per-token streaming. Three concurrent "users" submit prompts;
each consumes its stream as tokens are sampled (the first token
arrives after one prefill, not after the whole generation), and the
result is verified against the engine's synchronous path. The HTTP
twin (`POST /v1/generate`, chunked NDJSON) rides the same serving
front end as /v1/predict.

Run:
  JAX_PLATFORMS=cpu python examples/generate_stream.py
"""

import http.client
import json
import os
import sys
import threading

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu import generation  # noqa: E402
from paddle_tpu.generation.model import GPTConfig, build_lm_program  # noqa: E402
from paddle_tpu.inference import Config, create_predictor  # noqa: E402
from paddle_tpu.serving import ServingEngine, ServingServer  # noqa: E402


def export_lm(tmpdir, cfg, seq):
    main, startup, _feeds, fetches = build_lm_program(cfg, seq)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        fluid.io.save_inference_model(tmpdir, ["tokens"],
                                      [fetches["logits"]], exe, main)


def main(tmpdir="/tmp/pt_generate_model"):
    cfg = GPTConfig(vocab_size=151, hidden_size=48, num_layers=2,
                    num_heads=4, ffn_size=96, max_position=64,
                    hidden_dropout=0.0, attention_dropout=0.0)
    export_lm(tmpdir, cfg, 48)
    pred = create_predictor(Config(tmpdir))

    eng = generation.GenerationEngine(
        pred, cfg, page_size=8, num_pages=64, max_decode_batch=4,
        prefill_buckets=(16, 32), warmup=True)

    # 3 concurrent streaming users; all join the same decode batch
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, cfg.vocab_size, n).astype(np.int64)
               for n in (5, 9, 13)]
    streamed = {}

    def user(uid):
        toks = []
        for tok in eng.submit(prompts[uid], max_new_tokens=10):
            toks.append(tok)           # arrives as it is sampled
        streamed[uid] = toks

    threads = [threading.Thread(target=user, args=(u,)) for u in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    # streamed == synchronous (greedy decode is deterministic)
    for uid in range(3):
        assert streamed[uid] == eng.generate(prompts[uid],
                                             max_new_tokens=10), uid
    print("streams:", {u: streamed[u][:5] for u in sorted(streamed)})

    # the HTTP twin: chunked NDJSON from POST /v1/generate
    serve = ServingEngine(pred, start=False)
    srv = ServingServer(serve, generation_engine=eng)
    conn = http.client.HTTPConnection(srv.host, srv.port, timeout=120)
    conn.request("POST", "/v1/generate", json.dumps(
        {"tokens": [int(t) for t in prompts[0]], "max_new_tokens": 10}))
    resp = conn.getresponse()
    lines = [json.loads(ln) for ln in resp if ln.strip()]
    conn.close()
    assert lines[-1]["done"] and [ln["token"] for ln in
                                  lines[:-1]] == streamed[0]
    srv.close()
    serve.close()

    snap = eng.stats()
    print(f"decode occupancy {snap['decode_occupancy']:.2f}  "
          f"ttft p50 {snap['ttft_ms']['p50']:.1f}ms  "
          f"itl p50 {snap['itl_ms']['p50']:.1f}ms  "
          f"tokens/s {snap['decode_tokens_per_s']:.0f}")
    eng.close()
    print("streamed generation OK")


if __name__ == "__main__":
    main()
